"""Data layer: index maps, libsvm, GAME dataset build, entity blocking,
reservoir cap, Pearson selection, projection round-trips, stats, samplers.

Mirrors reference tests: PalDBIndexMapTest, AvroDataReaderIntegTest (format
level), RandomEffectDataSetTest grouping/cap semantics, LocalDataSetTest
feature filtering, BasicStatisticalSummaryTest, sampler tests.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import (
    BasicStatisticalSummary, FixedEffectDataConfig, FixedEffectDataset,
    GameDataset, IndexMap, IndexMapCollection, RandomEffectDataConfig,
    binary_classification_downsample, build_game_dataset, build_index_map,
    build_random_effect_dataset, feature_key, read_libsvm,
)
from photon_ml_tpu.ops import LOGISTIC
from photon_ml_tpu.optim import RegularizationContext, RegularizationType
from photon_ml_tpu.parallel import fit_random_effects, score_by_entity


def test_index_map_roundtrip(tmp_path):
    imap = build_index_map([("age", ""), ("height", "cm"), ("age", "bucket1")])
    assert imap.has_intercept and imap.intercept_index == imap.size - 1
    assert imap.index_of("age", "bucket1") >= 0
    assert imap.index_of("nope") == -1
    assert imap.name_term(imap.index_of("height", "cm")) == ("height", "cm")

    p = str(tmp_path / "maps")
    coll = IndexMapCollection({"global": imap})
    coll.save(p)
    loaded = IndexMapCollection.load(p)
    assert loaded.shards["global"].key_to_index == imap.key_to_index


def test_index_map_deterministic():
    a = build_index_map([("b", ""), ("a", ""), ("c", "")])
    b = build_index_map([("c", ""), ("a", ""), ("b", "")])
    assert a.key_to_index == b.key_to_index


def test_libsvm_reader(tmp_path):
    p = tmp_path / "tiny.libsvm"
    p.write_text("+1 1:0.5 3:2.0\n-1 2:1.5\n+1 1:1.0 2:0.25 3:-1\n")
    x, y = read_libsvm(str(p))
    assert x.shape == (3, 4)  # 3 features + intercept
    np.testing.assert_allclose(y, [1, 0, 1])
    np.testing.assert_allclose(x[0], [0.5, 0, 2.0, 1.0])
    np.testing.assert_allclose(x[1], [0, 1.5, 0, 1.0])


def _toy_game_dataset(rng, n=60, d=6, num_users=7):
    x = rng.normal(size=(n, d)); x[:, -1] = 1.0
    y = (rng.uniform(size=n) > 0.5).astype(float)
    users = rng.choice([f"u{i}" for i in range(num_users)], size=n)
    return build_game_dataset(
        y, {"global": x},
        entity_ids={"per_user": users},
        weights=rng.uniform(0.5, 1.5, size=n))


def test_game_dataset_build_and_subset(rng):
    ds = _toy_game_dataset(rng)
    assert ds.num_rows == 60
    assert set(ds.entity_indices) == {"per_user"}
    assert (ds.entity_indices["per_user"] >= 0).all()
    # subset shares vocab
    sub = ds.subset(np.arange(10))
    assert sub.num_rows == 10
    assert sub.entity_vocabs is ds.entity_vocabs


def test_game_dataset_unseen_entities_map_to_minus1(rng):
    ds = _toy_game_dataset(rng)
    ds2 = build_game_dataset(
        np.zeros(3), {"global": np.zeros((3, 6))},
        entity_ids={"per_user": np.asarray(["u0", "zzz_new", "u1"])},
        entity_vocabs=ds.entity_vocabs)
    assert ds2.entity_indices["per_user"][1] == -1
    assert ds2.entity_indices["per_user"][0] >= 0


def test_random_effect_dataset_identity_projector(rng):
    ds = _toy_game_dataset(rng)
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("per_user", "global", projector="identity"))
    E = red.num_entities
    assert E == len(np.unique(ds.entity_indices["per_user"]))
    # every real cell holds the right row
    for e in range(E):
        for s in range(red.blocks.samples_per_entity):
            r = red.active_row_ids[e, s]
            if r >= 0:
                np.testing.assert_allclose(np.asarray(red.blocks.x[e, s]),
                                           ds.feature_shards["global"][r])
                assert float(red.blocks.labels[e, s]) == ds.response[r]
    assert red.num_active == ds.num_rows


def test_random_effect_dataset_cap_rescales_weights(rng):
    ds = _toy_game_dataset(rng, n=200, num_users=3)
    cap = 10
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("per_user", "global",
                                   active_data_upper_bound=cap,
                                   projector="identity"))
    counts = np.bincount(ds.entity_indices["per_user"])
    for e in range(red.num_entities):
        vocab_idx = red.entity_ids[e]
        kept = int(np.asarray(red.blocks.mask[e]).sum())
        assert kept <= cap
        if counts[vocab_idx] > cap:
            # total weight preserved in expectation: scale = count/cap
            w = np.asarray(red.blocks.weights[e])
            orig_w = ds.weights[red.active_row_ids[e][red.active_row_ids[e] >= 0]]
            np.testing.assert_allclose(
                w[np.asarray(red.blocks.mask[e]) > 0],
                orig_w * counts[vocab_idx] / cap, rtol=1e-12)
    assert red.num_passive > 0


def test_index_map_projection_roundtrip(rng):
    """Projected training must equal identity-projector training once
    coefficients are scattered back to global space."""
    n, d = 80, 12
    x = np.zeros((n, d))
    users = np.asarray([f"u{i % 4}" for i in range(n)])
    # each user only observes its own feature slice (+ shared intercept)
    for i in range(n):
        u = i % 4
        x[i, u * 3: u * 3 + 2] = rng.normal(size=2)
    x[:, -1] = 1.0
    y = (rng.uniform(size=n) > 0.5).astype(float)
    ds = build_game_dataset(y, {"g": x}, entity_ids={"per_user": users})

    red_p = build_random_effect_dataset(
        ds, RandomEffectDataConfig("per_user", "g", projector="index_map"))
    red_i = build_random_effect_dataset(
        ds, RandomEffectDataConfig("per_user", "g", projector="identity"))
    assert red_p.local_dim < d  # actually projected

    reg = RegularizationContext(RegularizationType.L2)
    rp = fit_random_effects(red_p.blocks, LOGISTIC, reg=reg, reg_weight=0.5)
    ri = fit_random_effects(red_i.blocks, LOGISTIC, reg=reg, reg_weight=0.5)
    global_p = red_p.scatter_to_global(rp.x)
    np.testing.assert_allclose(np.asarray(global_p), np.asarray(ri.x),
                               rtol=1e-6, atol=1e-8)

    # flat scoring through entity lanes matches block scoring
    lanes = red_p.flat_entity_lanes(ds.entity_indices["per_user"])
    s_flat = score_by_entity(global_p, jnp.asarray(x), jnp.asarray(lanes))
    assert s_flat.shape == (n,)


def test_pearson_feature_selection(rng):
    n = 40
    d = 30
    x = rng.normal(size=(n, d))
    w_true = np.zeros(d); w_true[:3] = 3.0  # only first 3 informative
    y = (x @ w_true + 0.1 * rng.normal(size=n) > 0).astype(float)
    x[:, -1] = 1.0
    users = np.asarray(["u0"] * n)
    ds = build_game_dataset(y, {"g": x}, entity_ids={"per_user": users})
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("per_user", "g",
                                   features_to_samples_ratio=0.2,  # keep 8
                                   projector="index_map"))
    assert red.local_dim <= int(np.ceil(0.2 * n))
    kept = set(red.projection[0][red.projection[0] >= 0].tolist())
    assert {0, 1, 2} <= kept, f"informative features must survive, kept {kept}"
    assert d - 1 in kept, "the intercept must always survive feature selection"


def test_offsets_from_flat(rng):
    ds = _toy_game_dataset(rng)
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("per_user", "g" if "g" in ds.feature_shards else "global",
                                   projector="identity"))
    flat = rng.normal(size=ds.num_rows)
    blocks = red.with_offsets_from_flat(flat)
    for e in range(red.num_entities):
        for s in range(blocks.samples_per_entity):
            r = red.active_row_ids[e, s]
            if r >= 0:
                assert float(blocks.offsets[e, s]) == pytest.approx(flat[r])
            else:
                assert float(blocks.offsets[e, s]) == 0.0


class TestBucketedBuild:
    """S-bucketed RE build (VERDICT r2 item #2): multiple size classes, no
    hot-entity padding blowup, per-bucket solves equal the single-block
    solve."""

    def _skewed_dataset(self, rng, num_small=50, small_n=4, big_n=512, d=5):
        """num_small entities with small_n rows each + one hot entity."""
        sizes = [small_n] * num_small + [big_n]
        users, n = [], sum(sizes)
        for u, sz in enumerate(sizes):
            users += [f"u{u:04d}"] * sz
        x = rng.normal(size=(n, d)); x[:, -1] = 1.0
        y = (rng.uniform(size=n) < 0.5).astype(float)
        return build_game_dataset(y, {"g": x},
                                  entity_ids={"per_user": np.asarray(users)})

    def test_buckets_bound_padding(self, rng):
        ds = self._skewed_dataset(rng)
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfig("per_user", "g", projector="identity"))
        stats = red.padding_stats()
        assert stats["num_buckets"] >= 2
        # single-S layout wastes >90% of cells on this skew; buckets fix it
        assert stats["single_block_efficiency"] < 0.1
        assert stats["bucketed_efficiency"] > 0.9
        # lanes are count-descending and cover all rows exactly once
        per_lane = (np.asarray(red.active_row_ids) >= 0).sum(axis=1)
        assert (np.diff(per_lane) <= 0).all()
        assert red.num_active == ds.num_rows
        ids = np.asarray(red.active_row_ids)
        real = np.sort(ids[ids >= 0])
        np.testing.assert_array_equal(real, np.arange(ds.num_rows))

    def test_bucketed_solve_equals_single_block(self, rng):
        ds = self._skewed_dataset(rng, num_small=10, big_n=64)
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfig("per_user", "g", projector="identity"))
        assert len(red.buckets) >= 2
        reg = RegularizationContext(RegularizationType.L2)
        parts = [fit_random_effects(b.blocks, LOGISTIC, reg=reg, reg_weight=0.5).x
                 for b in red.buckets]
        per_bucket = np.concatenate([np.asarray(p) for p in parts])
        single = np.asarray(fit_random_effects(red.blocks, LOGISTIC, reg=reg,
                                               reg_weight=0.5).x)
        np.testing.assert_allclose(per_bucket, single, rtol=1e-6, atol=1e-8)

    def test_bucketed_game_training_matches_history(self, rng):
        """End-to-end: GAME fit over a skewed dataset produces a finite,
        decreasing objective with the bucketed RE path."""
        from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                        GameEstimator, GameTrainingConfig,
                                        GLMOptimizationConfig,
                                        RandomEffectCoordinateConfig)
        ds = self._skewed_dataset(rng, num_small=12, big_n=96)
        cfg = GameTrainingConfig(
            task_type="logistic_regression",
            coordinates={
                "fixed": FixedEffectCoordinateConfig(
                    "g", GLMOptimizationConfig(regularization_weight=0.1)),
                "perUser": RandomEffectCoordinateConfig(
                    random_effect_type="per_user", feature_shard="g",
                    optimization=GLMOptimizationConfig(regularization_weight=1.0)),
            },
            updating_sequence=["fixed", "perUser"], num_outer_iterations=2)
        res = GameEstimator(cfg).fit(ds)
        hist = res.objective_history
        assert np.isfinite(hist).all() and hist[-1] <= hist[0]

    def test_million_entity_build_seconds(self, rng):
        # VERDICT r2 item #2 gate: 1e6-entity build in seconds, not O(E) loops
        import time
        E, d = 1_000_000, 8
        n = 3 * E
        users = rng.integers(0, E, size=n)
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        ds = build_game_dataset(y, {"g": x}, entity_ids={"per_user": users})
        t0 = time.perf_counter()
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfig("per_user", "g", projector="identity",
                                       active_data_upper_bound=16),
            dtype=np.float32)
        dt = time.perf_counter() - t0
        assert red.num_entities <= E
        assert red.padding_stats()["bucketed_efficiency"] > 0.5
        assert dt < 60.0, f"1e6-entity build took {dt:.1f}s"


def test_stats_summary(rng):
    x = rng.normal(size=(50, 4)); x[:, 2] = 0.0
    s = BasicStatisticalSummary.from_features(x)
    np.testing.assert_allclose(s.mean, x.mean(0))
    np.testing.assert_allclose(s.variance, x.var(0, ddof=1))
    assert s.num_nonzeros[2] == 0
    assert s.count == 50
    np.testing.assert_allclose(s.max_magnitude, np.abs(x).max(0))


def test_binary_downsampler_unbiased(rng):
    labels = jnp.asarray((np.arange(10000) % 4 == 0).astype(float))  # 25% pos
    key = jax.random.PRNGKey(0)
    mask, w = binary_classification_downsample(key, labels, None, 0.3)
    # all positives kept
    assert bool(jnp.all(mask[labels > 0.5] == 1.0))
    # negative weight sum approximately preserved
    neg = labels < 0.5
    kept_negative_weight = float(jnp.sum(mask[neg] * w[neg]))
    assert abs(kept_negative_weight - float(jnp.sum(neg))) / float(jnp.sum(neg)) < 0.05
    with pytest.raises(ValueError):
        binary_classification_downsample(key, labels, None, 1.5)


def test_sparse_summary_matches_dense(rng):
    """BasicStatisticalSummary.from_sparse == from_features on the
    densified shard (the wide-regime stats path never densifies)."""
    import scipy.sparse as sp

    from photon_ml_tpu.data.stats import BasicStatisticalSummary

    x = sp.random(50, 12, density=0.3, format="csr", random_state=5)
    w = rng.uniform(0.5, 2.0, 50)
    for weights in (None, w):
        a = BasicStatisticalSummary.from_sparse(x, weights)
        b = BasicStatisticalSummary.from_features(x.toarray(), weights)
        for field in ("mean", "variance", "num_nonzeros", "max", "min",
                      "norm_l1", "norm_l2", "mean_abs"):
            np.testing.assert_allclose(getattr(a, field), getattr(b, field),
                                       rtol=1e-10, atol=1e-12, err_msg=field)
