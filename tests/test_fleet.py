"""Replicated serving fleet tests (photon_ml_tpu/fleet/) — ISSUE 12.

Covers the replication log's durability discipline (bit-exact array round
trips, torn-tail recovery, segment rotation, gap/corruption detection,
compaction folding), the replica lifecycle (join -> catch-up -> ready ->
drain -> crash -> rejoin, run with the lock tracker ARMED and validated
against the static lock-order graph), bit-identical convergence across
deltas / rollbacks / swaps, the `replog.*`/`replica.apply` fault sites,
the front's probe/failover/hedge/backpressure behavior against stub
replicas, and the ISSUE 12 satellites: graceful SIGTERM drain (via
subprocess), loud undo-log-overflow degradation, the StaleDeltaError
re-enqueue racing a concurrent full install, and feedback 429s carrying
Retry-After derived from the updater's drain rate.
"""
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import photon_ml_tpu

from photon_ml_tpu.fleet import (FleetPublisher, Front, FrontConfig,
                                 NoReadyReplica, Replica, ReplicaConfig,
                                 ReplicationLog, ReplicationLogError,
                                 decode_array, encode_array)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.models.io import save_game_model
from photon_ml_tpu.online import OnlineUpdateConfig
from photon_ml_tpu.serving import (Overloaded, ScoringService,
                                   ServingConfig)
from photon_ml_tpu.utils import faults, locktrace

D_G, D_U, N_ENT = 6, 4, 30
TASK = "logistic_regression"
PACKAGE_DIR = os.path.dirname(os.path.abspath(photon_ml_tpu.__file__))
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _make_model(rng, coef_scale=1.0):
    fe = FixedEffectModel(
        model_for_task(TASK, Coefficients(
            jnp.asarray(coef_scale * rng.normal(size=D_G)))), "global")
    re = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type=TASK,
        coefficients=jnp.asarray(coef_scale * rng.normal(size=(N_ENT, D_U))),
        entity_ids=np.asarray([f"u{i}" for i in range(N_ENT)], dtype=object),
        projection=None, global_dim=D_U)
    return GameModel({"fixed": fe, "perUser": re}, TASK)


def _save_model(rng, tmp_path, name="model", coef_scale=1.0):
    mdir = str(tmp_path / name)
    save_game_model(_make_model(rng, coef_scale), mdir)
    return mdir


def _service(mdir, *, updates=False):
    return ScoringService(
        model_dir=mdir, config=ServingConfig(max_batch=64, min_bucket=4),
        updates=OnlineUpdateConfig(micro_batch=8) if updates else None,
        start_updater=False)


def _publisher(mdir, log_dir):
    svc = _service(mdir, updates=True)
    log = ReplicationLog(str(log_dir))
    pub = FleetPublisher(svc, log, model_dir=mdir)
    return svc, log, pub


def _follower(mdir, log, state_dir, join=True):
    svc = _service(mdir)
    rep = Replica(svc, log, str(state_dir), ReplicaConfig())
    if join:
        rep.join()
    return rep


def _feedback(svc, seed, n=16):
    r = np.random.default_rng(seed)
    feats = {"global": r.normal(size=(n, D_G)),
             "per_user": r.normal(size=(n, D_U))}
    ids = {"userId": np.asarray(
        [f"u{r.integers(0, N_ENT)}" for _ in range(n)], dtype=object)}
    labels = (r.uniform(size=n) < 0.5).astype(float)
    svc.feedback(feats, ids, labels)
    svc.updater.flush()


def _audits_equal(*services):
    audits = [s.audit() for s in services]
    return all(a["version_vector"] == audits[0]["version_vector"]
               and a["table_hashes"] == audits[0]["table_hashes"]
               for a in audits[1:])


# --------------------------------------------------------------------------
# replication log
# --------------------------------------------------------------------------

def test_array_codec_bit_exact(rng):
    for a in (rng.normal(size=(5, 3)),
              rng.normal(size=7).astype(np.float32),
              np.arange(4, dtype=np.int64)):
        b = decode_array(encode_array(a))
        assert b.dtype == a.dtype and b.shape == a.shape
        assert a.tobytes() == b.tobytes()


def test_replog_append_read_roundtrip(tmp_path, rng):
    log = ReplicationLog(str(tmp_path / "log"))
    values = rng.normal(size=(3, D_U))
    seq1 = log.append({"kind": "swap", "version": "v1",
                       "previous_version": None, "source_dir": "/m"})
    seq2 = log.append({"kind": "delta", "version": "v1",
                       "base_version": "v1", "delta_seq": 1,
                       "created_at": 0.0,
                       "coordinates": {"perUser": {
                           "rows": encode_array(np.arange(3)),
                           "values": encode_array(values),
                           "prior": encode_array(values * 0)}}})
    assert (seq1, seq2) == (1, 2)
    assert log.head_seq() == 2
    records = log.read(0)
    assert [r["log_seq"] for r in records] == [1, 2]
    got = decode_array(
        records[1]["record"]["coordinates"]["perUser"]["values"])
    assert got.tobytes() == values.tobytes()   # bit-exact round trip
    assert log.read(2) == []


def test_replog_torn_tail_ignored_and_recovered(tmp_path):
    log = ReplicationLog(str(tmp_path / "log"))
    for k in range(3):
        log.append({"kind": "rollback", "version": f"v{k}",
                    "previous_version": None, "degraded": False})
    seg = [f for f in os.listdir(log.log_dir) if f.startswith("segment")]
    path = os.path.join(log.log_dir, seg[0])
    with open(path, "a") as f:
        f.write('{"log_seq": 4, "t": 0, "record"')  # torn mid-append
    reader = ReplicationLog(str(tmp_path / "log"))
    assert [r["log_seq"] for r in reader.read(0)] == [1, 2, 3]
    # publisher-side open repairs the tail and appends cleanly after
    writer = ReplicationLog(str(tmp_path / "log"))
    assert writer.recover() > 0
    assert writer.recover() == 0
    assert writer.append({"kind": "rollback", "version": "v3",
                          "previous_version": None,
                          "degraded": False}) == 4


def test_replog_mid_file_corruption_raises(tmp_path):
    log = ReplicationLog(str(tmp_path / "log"))
    for k in range(2):
        log.append({"kind": "rollback", "version": f"v{k}",
                    "previous_version": None, "degraded": False})
    seg = [f for f in os.listdir(log.log_dir) if f.startswith("segment")]
    path = os.path.join(log.log_dir, seg[0])
    lines = open(path).readlines()
    lines[0] = lines[0].replace("v0", "vX")  # checksum now mismatches
    with open(path, "w") as f:
        f.writelines(lines)
    with pytest.raises(ReplicationLogError, match="corrupt"):
        ReplicationLog(str(tmp_path / "log")).read(0)


def test_replog_segment_rotation_and_order(tmp_path):
    log = ReplicationLog(str(tmp_path / "log"), segment_records=2)
    for k in range(5):
        log.append({"kind": "rollback", "version": f"v{k}",
                    "previous_version": None, "degraded": False})
    segs = [f for f in os.listdir(log.log_dir) if f.startswith("segment")]
    assert len(segs) == 3
    assert [r["log_seq"] for r in log.read(0)] == [1, 2, 3, 4, 5]
    assert [r["record"]["version"] for r in log.read(3)] == ["v3", "v4"]


def test_replog_fault_sites_fire(tmp_path):
    log = ReplicationLog(str(tmp_path / "log"))
    plan = faults.FaultPlan([
        {"site": "replog.append", "action": "fatal", "hits": [1]},
        {"site": "replog.read", "action": "transient", "hits": [1]},
    ])
    with faults.injected(plan):
        with pytest.raises(faults.FatalFault):
            log.append({"kind": "rollback", "version": "v",
                        "previous_version": None, "degraded": False})
        with pytest.raises(faults.TransientFault):
            log.read(0)
    assert plan.report()["total_fired"] == 2
    # the fatal append wrote NOTHING (fires before the write)
    assert log.head_seq() == 0


# --------------------------------------------------------------------------
# replica runtime: convergence, crash resume, compaction
# --------------------------------------------------------------------------

def test_replica_converges_bit_identically(tmp_path, rng):
    mdir = _save_model(rng, tmp_path)
    svc, log, _pub = _publisher(mdir, tmp_path / "log")
    rep = _follower(mdir, log, tmp_path / "s0")
    try:
        for s in range(3):
            _feedback(svc, 100 + s)
        assert rep.poll_once() > 0
        assert _audits_equal(svc, rep.service)
        assert rep.status()["lag_seq"] == 0
        # replica-side fleet gauges landed on the metric surface
        snap = rep.service.metrics_snapshot()
        assert snap["fleet"]["applied_seq"] == log.head_seq()
        assert snap["fleet"]["ready"] == 1
        assert snap["fleet"]["records_applied"] > 0
    finally:
        svc.close()
        rep.service.close()


def test_replica_replays_swap_and_full_rollback(tmp_path, rng):
    mdir = _save_model(rng, tmp_path)
    mdir2 = _save_model(np.random.default_rng(11), tmp_path, "model2", 1.5)
    svc, log, _pub = _publisher(mdir, tmp_path / "log")
    rep = _follower(mdir, log, tmp_path / "s0")
    try:
        _feedback(svc, 200)
        svc.swap(mdir2, version="v2")      # full swap rides the log
        _feedback(svc, 201)
        rep.poll_once()
        assert _audits_equal(svc, rep.service)
        assert rep.service.model_version == "v2"
        svc.rollback()                      # delta-aware (v2's deltas)
        svc.rollback()                      # full-model: back to v1
        rep.poll_once()
        assert _audits_equal(svc, rep.service)
        assert rep.service.model_version == svc.model_version != "v2"
    finally:
        svc.close()
        rep.service.close()


def test_replica_crash_resume_is_idempotent(tmp_path, rng):
    """A restart resumes from the durable (applied seq + folded table
    state) pair; a STALE-but-consistent durable state — the crash landed
    between an apply and its ack — replays the already-applied tail
    idempotently and still converges bit-identically."""
    mdir = _save_model(rng, tmp_path)
    svc, log, _pub = _publisher(mdir, tmp_path / "log")
    rep = _follower(mdir, log, tmp_path / "s0")
    _feedback(svc, 300)
    rep.poll_once()
    early_state = (tmp_path / "s0" / "applied.json").read_text()
    early_applied = rep.status()["applied_seq"]
    for s in range(1, 3):
        _feedback(svc, 300 + s)
    rep.poll_once()
    assert rep.status()["applied_seq"] == log.head_seq()
    rep.service.close()
    # crash: the process dies AFTER applying the newest records but
    # BEFORE their ack became durable — the state dir still holds the
    # earlier (seq, fold) pair
    (tmp_path / "s0" / "applied.json").write_text(early_state)
    rep2 = _follower(mdir, log, tmp_path / "s0")
    services = [rep2.service]
    try:
        info2 = rep2.status()
        assert info2["applied_seq"] == log.head_seq()
        assert info2["applied_seq"] > early_applied
        assert _audits_equal(svc, rep2.service)
        # and a clean (non-stale) restart resumes without replaying
        rep3 = _follower(mdir, log, tmp_path / "s0")
        services.append(rep3.service)
        assert _audits_equal(svc, rep3.service)
    finally:
        svc.close()
        for s in services:
            s.close()


def test_compaction_snapshot_join(tmp_path, rng):
    mdir = _save_model(rng, tmp_path)
    svc, log, _pub = _publisher(mdir, tmp_path / "log")
    try:
        for s in range(3):
            _feedback(svc, 400 + s)
        svc.rollback()
        _feedback(svc, 403)
        snap = log.compact(log.head_seq())
        assert snap["upto_seq"] == log.head_seq()
        assert not [f for f in os.listdir(log.log_dir)
                    if f.startswith("segment")]
        # a fresh replica bootstraps from the snapshot alone
        rep = _follower(mdir, log, tmp_path / "s_new")
        try:
            assert _audits_equal(svc, rep.service)
        finally:
            rep.service.close()
        # compacted history refuses a read that predates the snapshot
        _feedback(svc, 404)
        with pytest.raises(ReplicationLogError, match="compacted"):
            log.read(1)
    finally:
        svc.close()


def test_replica_transient_apply_faults_absorbed(tmp_path, rng):
    mdir = _save_model(rng, tmp_path)
    svc, log, _pub = _publisher(mdir, tmp_path / "log")
    rep = _follower(mdir, log, tmp_path / "s0")
    try:
        for s in range(2):
            _feedback(svc, 500 + s)
        plan = faults.FaultPlan([
            {"site": "replica.apply", "action": "transient",
             "hits": [1, 2]},
            {"site": "replog.read", "action": "transient", "hits": [1]},
        ])
        with faults.injected(plan):
            rep.poll_once()
        assert plan.report()["total_fired"] == 3
        assert _audits_equal(svc, rep.service)
        assert rep.service.metrics_snapshot()["fleet"]["apply_retries"] >= 3
        assert rep.healthy()
    finally:
        svc.close()
        rep.service.close()


def test_replica_fatal_apply_marks_failed(tmp_path, rng, caplog):
    mdir = _save_model(rng, tmp_path)
    svc, log, _pub = _publisher(mdir, tmp_path / "log")
    rep = _follower(mdir, log, tmp_path / "s0")
    try:
        _feedback(svc, 600)
        plan = faults.FaultPlan([
            {"site": "replica.apply", "action": "fatal",
             "probability": 1.0},
        ])
        with caplog.at_level(logging.ERROR, logger="photon_ml_tpu"):
            with faults.injected(plan):
                assert rep.poll_once() == 0
        assert not rep.healthy()
        assert rep.status()["failed"] is not None
        assert any("FAILED" in r.message for r in caplog.records)
        assert rep.poll_once() == 0   # failed replicas stop applying
    finally:
        svc.close()
        rep.service.close()


def test_fleet_lifecycle_with_locktrace_armed(tmp_path):
    """ISSUE 12 acceptance: the full lifecycle — join -> catch-up ->
    ready -> drain -> crash -> rejoin — under the ARMED lock tracker,
    with every observed acquisition order an edge consistent with the
    static lock-order graph, and all three fleet locks actually
    exercised."""
    r = np.random.default_rng(21)
    with locktrace.enabled() as tracker:
        mdir = _save_model(r, tmp_path)
        svc, log, _pub = _publisher(mdir, tmp_path / "log")
        rep = _follower(mdir, log, tmp_path / "s0", join=False)
        errors = []
        stop = threading.Event()

        def score_loop():
            rr = np.random.default_rng(23)
            while not stop.is_set():
                try:
                    rep.service.score(
                        {"global": rr.normal(size=(2, D_G)),
                         "per_user": rr.normal(size=(2, D_U))},
                        {"userId": np.asarray(["u1", "u2"], dtype=object)})
                except Exception as e:  # pragma: no cover
                    errors.append(f"{type(e).__name__}: {e}")

        try:
            _feedback(svc, 700)
            info = rep.join()                       # join -> catch-up
            assert info["records_replayed"] >= 1
            assert rep.healthy()                    # ready
            t = threading.Thread(target=score_loop, daemon=True)
            t.start()
            _feedback(svc, 701)
            rep.start()                             # background apply
            deadline = time.time() + 10
            while rep.status()["applied_seq"] < log.head_seq() \
                    and time.time() < deadline:
                time.sleep(0.02)
            rep.drain()                             # drain
            assert not rep.healthy()
            assert rep.poll_once() == 0
            stop.set()
            t.join(timeout=5)
            rep.close()
            rep.service.close()                     # crash (abrupt stop)
            svc2 = _service(mdir)
            rep2 = Replica(svc2, log, str(tmp_path / "s0"),
                           ReplicaConfig())
            rep2.join()                             # rejoin
            assert _audits_equal(svc, rep2.service)
            svc2.close()
        finally:
            stop.set()
            svc.close()
    assert errors == []
    from photon_ml_tpu.analysis.concurrency import lock_order_edges
    tracker.assert_consistent(lock_order_edges([PACKAGE_DIR]))
    acq = tracker.acquisitions()
    assert acq.get("Replica._lock", 0) > 0
    assert acq.get("ReplicationLog._lock", 0) > 0
    assert acq.get("FleetPublisher._lock", 0) > 0


# --------------------------------------------------------------------------
# front: probes, failover, hedging, backpressure, drain (stub replicas)
# --------------------------------------------------------------------------

class _StubReplica:
    """A minimal HTTP replica: switchable health, optional latency,
    canned /score responses — the front's behavior is protocol-level, so
    stubs make failover/hedging deterministic and fast."""

    def __init__(self, name):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *a):
                pass

            def _reply(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    ok = stub.healthy
                    self._reply(200 if ok else 503, {
                        "status": "ok" if ok else "degraded",
                        "fleet": {"applied_seq": stub.applied_seq}})
                else:
                    self._reply(404, {})

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(length)
                if stub.delay_s:
                    time.sleep(stub.delay_s)
                stub.hits += 1
                if self.path == "/score":
                    self._reply(200, {"scores": [0.0], "served_by": name})
                elif self.path == "/feedback":
                    self._reply(stub.feedback_status,
                                {"served_by": name},
                                {"Retry-After": "7"}
                                if stub.feedback_status == 429 else None)
                elif self.path == "/fleet/drain":
                    stub.drained = True
                    self._reply(200, {"draining": True})
                else:
                    self._reply(404, {})

        self.name = name
        self.healthy = True
        self.applied_seq = 0
        self.delay_s = 0.0
        self.hits = 0
        self.drained = False
        self.feedback_status = 202
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        kwargs={"poll_interval": 0.05},
                                        daemon=True)
        self._thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)


@pytest.fixture
def stubs():
    pair = [_StubReplica("a"), _StubReplica("b")]
    yield pair
    for s in pair:
        s.close()


def _front(stubs, **cfg_kw):
    cfg_kw.setdefault("probe_interval_s", 0.05)
    cfg_kw.setdefault("hedge_after_s", 5.0)
    cfg_kw.setdefault("request_timeout_s", 5.0)
    front = Front([s.url for s in stubs], config=FrontConfig(**cfg_kw),
                  start_probes=False)
    front.probe_once()
    return front


def test_front_round_robin_over_ready(stubs):
    front = _front(stubs)
    try:
        for _ in range(6):
            status, payload = front.route("/score", {})
            assert status == 200
        assert stubs[0].hits == 3 and stubs[1].hits == 3
        assert front.status()["ready_replicas"] == 2
    finally:
        front.close()


def test_front_unready_replica_leaves_rotation(stubs):
    front = _front(stubs, unhealthy_after=1)
    try:
        stubs[1].healthy = False               # e.g. a PR 11 health gate
        front.probe_once()
        for _ in range(4):
            assert front.route("/score", {})[0] == 200
        assert stubs[1].hits == 0
        stubs[1].healthy = True                # recovers
        front.probe_once()
        for _ in range(2):
            front.route("/score", {})
        assert stubs[1].hits > 0
        # probe payloads feed the lag gauge
        stubs[0].applied_seq, stubs[1].applied_seq = 9, 4
        front.probe_once()
        assert front.metrics_snapshot()["gauges"][
            "fleet.front_max_lag_seq"] == 5
    finally:
        front.close()


def test_front_failover_on_dead_replica(stubs):
    front = _front(stubs)
    try:
        stubs[0].close()                       # transport-level death
        ok = 0
        for _ in range(4):
            status, payload = front.route("/score", {})
            assert status == 200 and payload["served_by"] == "b"
            ok += 1
        assert ok == 4
        snap = front.metrics_snapshot()["counters"]
        assert snap["fleet.front_failovers"] >= 1
    finally:
        front.close()


def test_front_hedges_slow_replica(stubs):
    front = _front(stubs, hedge_after_s=0.1)
    try:
        stubs[0].delay_s = 2.0                 # slow, not dead
        t0 = time.monotonic()
        status, payload = front.route("/score", {})
        elapsed = time.monotonic() - t0
        assert status == 200
        assert payload["served_by"] == "b"     # the hedge won
        assert elapsed < 1.5                   # did not wait out the slow one
        assert front.metrics_snapshot()["counters"][
            "fleet.front_hedges"] >= 1
    finally:
        front.close()


def test_front_backpressure_sheds(stubs):
    front = _front(stubs, max_inflight=0)
    try:
        with pytest.raises(Overloaded):
            front.route("/score", {})
        assert front.metrics_snapshot()["counters"][
            "fleet.front_shed"] == 1
    finally:
        front.close()


def test_front_no_ready_replica_raises(stubs):
    front = _front(stubs, unhealthy_after=1)
    try:
        stubs[0].healthy = stubs[1].healthy = False
        front.probe_once()
        with pytest.raises(NoReadyReplica):
            front.route("/score", {})
    finally:
        front.close()


def test_front_publisher_routing_and_retry_after_passthrough(stubs):
    front = _front(stubs)
    try:
        status, payload, headers = front.route_publisher(
            "POST", "/feedback", {"labels": [1.0]})
        assert status == 202
        assert payload["served_by"] == "a"     # first URL is the publisher
        stubs[0].feedback_status = 429
        status, _payload, headers = front.route_publisher(
            "POST", "/feedback", {"labels": [1.0]})
        assert status == 429
        assert headers["Retry-After"] == "7"   # backpressure hint rides up
    finally:
        front.close()


def test_front_drain_detaches(stubs):
    front = _front(stubs)
    try:
        out = front.drain(stubs[0].url)
        assert out["detached"] is True
        assert stubs[0].drained is True
        hits0 = stubs[0].hits
        for _ in range(3):
            assert front.route("/score", {})[0] == 200
        assert stubs[0].hits == hits0          # no longer routed to
        assert front.status()["ready_replicas"] == 1
    finally:
        front.close()


# --------------------------------------------------------------------------
# satellites
# --------------------------------------------------------------------------

def test_registry_overflow_degrades_loudly(tmp_path, rng, caplog):
    """Satellite: undo-log overflow is configurable and LOUD — the
    overflow logs an error, rollback degrades to the full-model path,
    and serve.rollback_degraded lands on both metric surfaces."""
    mdir = _save_model(rng, tmp_path)
    mdir2 = _save_model(np.random.default_rng(31), tmp_path, "m2", 1.5)
    svc = ScoringService(
        model_dir=mdir,
        config=ServingConfig(max_batch=64, min_bucket=4, max_delta_log=2),
        updates=OnlineUpdateConfig(micro_batch=4), start_updater=False)
    try:
        v1 = svc.model_version
        svc.swap(mdir2, version="v2")
        with caplog.at_level(logging.ERROR, logger="photon_ml_tpu"):
            while svc.registry.pending_deltas() < 2 or \
                    not svc.registry._delta_log_truncated:
                _feedback(svc, int(svc.version_vector()["delta_seq"]))
        assert any("overflowed" in r.message for r in caplog.records)
        table_before = np.asarray(
            svc.registry.scorer.re_table("perUser")).copy()
        with caplog.at_level(logging.ERROR, logger="photon_ml_tpu"):
            got = svc.rollback()
        assert got == v1                       # degraded to full-model
        assert any("DEGRADED" in r.message for r in caplog.records)
        snap = svc.metrics_snapshot()
        assert snap["rollback_degraded"] == 1
        assert "photon_serve_rollback_degraded_total 1" in \
            svc.prometheus_metrics()
        # the exact pre-delta rows are NOT restored (that is the point
        # of the degradation being loud)
        assert not np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")),
            table_before)
    finally:
        svc.close()


def test_registry_overflow_without_previous_raises(tmp_path, rng):
    mdir = _save_model(rng, tmp_path)
    svc = ScoringService(
        model_dir=mdir,
        config=ServingConfig(max_batch=64, min_bucket=4, max_delta_log=1),
        updates=OnlineUpdateConfig(micro_batch=4), start_updater=False)
    try:
        while not svc.registry._delta_log_truncated:
            _feedback(svc, int(svc.version_vector()["delta_seq"]) + 40)
        with pytest.raises(RuntimeError, match="known-good"):
            svc.rollback()
        assert svc.metrics_snapshot()["rollback_degraded"] == 0
    finally:
        svc.close()


def test_exact_rollback_path_keeps_degraded_counter_zero(tmp_path, rng):
    mdir = _save_model(rng, tmp_path)
    svc = ScoringService(
        model_dir=mdir,
        config=ServingConfig(max_batch=64, min_bucket=4,
                             max_delta_log=64),
        updates=OnlineUpdateConfig(micro_batch=8), start_updater=False)
    try:
        table0 = np.asarray(svc.registry.scorer.re_table("perUser")).copy()
        _feedback(svc, 800)
        svc.rollback()
        assert np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")), table0)
        assert svc.metrics_snapshot()["rollback_degraded"] == 0
    finally:
        svc.close()


def test_stale_delta_reenqueue_races_concurrent_install(tmp_path, rng):
    """Satellite: a full install() landing between the updater's solve
    and its publish surfaces as StaleDeltaError — the feedback
    re-enqueues, the re-solve runs against the NEW version, and no delta
    from the old base ever lands.  Run with locktrace ARMED and
    validated against the static lock graph."""
    from photon_ml_tpu.serving import CompiledScorer
    with locktrace.enabled() as tracker:
        mdir = _save_model(rng, tmp_path)
        svc = ScoringService(
            model_dir=mdir,
            config=ServingConfig(max_batch=64, min_bucket=4),
            updates=OnlineUpdateConfig(micro_batch=8),
            start_updater=False)
        try:
            v1 = svc.model_version
            scorer2 = CompiledScorer(_make_model(np.random.default_rng(41)),
                                     max_batch=64, min_bucket=4)
            scorer2.warmup()
            real_solve = svc.updater._solve_with_retry
            installed = []

            def solve_then_install(lane, blocks, prior):
                out = real_solve(lane, blocks, prior)
                if not installed:      # exactly one racing install
                    svc.registry.install(scorer2, "v2")
                    installed.append(True)
                return out

            svc.updater._solve_with_retry = solve_then_install
            r = np.random.default_rng(43)
            feats = {"global": r.normal(size=(8, D_G)),
                     "per_user": r.normal(size=(8, D_U))}
            ids = {"userId": np.asarray(
                [f"u{i}" for i in range(8)], dtype=object)}
            labels = (r.uniform(size=8) < 0.5).astype(float)
            svc.feedback(feats, ids, labels)
            out1 = svc.updater.run_once()
            # the racing install won: nothing published this cycle
            assert out1["deltas"] == 0
            snap = svc.metrics_snapshot()
            assert snap["online"]["stale_deltas"] == 1
            assert svc.model_version == "v2"
            # the re-enqueued feedback re-solves against v2 next cycle
            out2 = svc.updater.run_once()
            assert out2["deltas"] >= 1
            assert svc.updater.buffer.pending_rows == 0
            deltas = svc.registry.applied_deltas()
            assert deltas and all(d.base_version == "v2" for d in deltas)
            assert v1 not in {d.base_version for d in deltas}
        finally:
            svc.close()
    from photon_ml_tpu.analysis.concurrency import lock_order_edges
    tracker.assert_consistent(lock_order_edges([PACKAGE_DIR]))
    assert tracker.acquisitions().get("ModelRegistry._lock", 0) > 0


def test_feedback_429_carries_retry_after(tmp_path, rng):
    """Satellite: a whole-batch feedback rejection carries a drain-rate
    derived retry_after_s and counts online.feedback_rejected on both
    metric surfaces."""
    mdir = _save_model(rng, tmp_path)
    svc = ScoringService(
        model_dir=mdir, config=ServingConfig(max_batch=64, min_bucket=4),
        updates=OnlineUpdateConfig(micro_batch=4, max_pending_rows=4),
        start_updater=False)
    try:
        r = np.random.default_rng(53)
        n = 16                                 # > max_pending_rows: whole
        feats = {"global": r.normal(size=(n, D_G)),  # batch rejected
                 "per_user": r.normal(size=(n, D_U))}
        ids = {"userId": np.asarray(
            [f"u{i % N_ENT}" for i in range(n)], dtype=object)}
        labels = np.zeros(n)
        with pytest.raises(Overloaded) as exc:
            svc.feedback(feats, ids, labels)
        assert exc.value.retry_after_s > 0
        snap = svc.metrics_snapshot()
        assert snap["online"]["feedback_rejected"] == 1
        assert "photon_online_feedback_rejected_total 1" in \
            svc.prometheus_metrics()
        # once the updater has drained, the estimate follows the
        # observed rate instead of the poll-interval floor
        _feedback(svc, 900, n=4)
        assert svc.updater.retry_after_s() >= \
            svc.updater.config.interval_s
    finally:
        svc.close()


def test_table_hashes_track_delta_state(tmp_path, rng):
    mdir = _save_model(rng, tmp_path)
    svc = ScoringService(
        model_dir=mdir, config=ServingConfig(max_batch=64, min_bucket=4),
        updates=OnlineUpdateConfig(micro_batch=8), start_updater=False)
    try:
        h0 = svc.registry.scorer.table_hashes()
        assert set(h0) == {"fixed", "perUser"}
        _feedback(svc, 950)
        h1 = svc.registry.scorer.table_hashes()
        assert h1["perUser"] != h0["perUser"]
        assert h1["fixed"] == h0["fixed"]      # FE untouched by deltas
        svc.rollback()
        assert svc.registry.scorer.table_hashes() == h0  # bit-exact
    finally:
        svc.close()


@pytest.mark.parametrize("fill_buffer", [False, True])
def test_graceful_drain_sigterm_subprocess(tmp_path, fill_buffer):
    """Satellite: SIGTERM drains the serve CLI cleanly — stop accepting,
    finish in-flight, flush the FeedbackBuffer through the updater,
    close, exit 0 with a final drained line.  The fill_buffer variant
    also exercises the HTTP 429 + Retry-After path first."""
    import urllib.error
    import urllib.request

    r = np.random.default_rng(61)
    mdir = str(tmp_path / "model")
    save_game_model(_make_model(r), mdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.cli.serve",
         "--model-dir", mdir, "--port", "0", "--max-batch", "32",
         "--min-bucket", "4", "--enable-updates",
         "--feedback-max-pending", "8" if fill_buffer else "1024",
         "--update-interval-ms", "50"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    try:
        info = json.loads(proc.stdout.readline())
        url = info["serving"]

        def post(path, body):
            req = urllib.request.Request(
                url + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=15) as resp:
                    return resp.status, dict(resp.headers), \
                        json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), json.loads(e.read())

        n = 16
        body = {"features": {
            "global": r.normal(size=(n, D_G)).tolist(),
            "per_user": r.normal(size=(n, D_U)).tolist()},
            "ids": {"userId": [f"u{i % N_ENT}" for i in range(n)]},
            "labels": [0.0] * n}
        if fill_buffer:
            status, headers, payload = post("/feedback", body)
            assert status == 429
            assert int(headers["Retry-After"]) >= 1
            assert payload["retry_after_s"] > 0
        else:
            status, _headers, _payload = post("/feedback", body)
            assert status == 202
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=10)
    assert proc.returncode == 0
    last = json.loads(out.strip().splitlines()[-1])
    assert last["drained"] is True and last["aborted"] is False
    if not fill_buffer:
        # the drain flushed the buffered feedback before exit
        assert last["feedback_flushed"] is not None
        assert last["version_vector"]["delta_seq"] >= 1
