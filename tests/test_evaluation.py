"""Evaluator correctness vs sklearn-free closed forms and brute force.

Mirrors reference: AreaUnderROCCurveEvaluatorTest / LocalEvaluator tests /
MultiEvaluator grouping tests.
"""
import dataclasses
import time

import numpy as np
import pytest

from photon_ml_tpu.evaluation import (
    AUC, RMSE, MultiEvaluator, area_under_roc_curve,
    default_validation_evaluator_for_task, parse_evaluator, precision_at_k,
)


def _brute_auc(s, y, w=None):
    s, y = np.asarray(s, float), np.asarray(y, float)
    w = np.ones_like(s) if w is None else np.asarray(w, float)
    num = den = 0.0
    for i in np.nonzero(y > 0.5)[0]:
        for j in np.nonzero(y <= 0.5)[0]:
            ww = w[i] * w[j]
            den += ww
            if s[i] > s[j]:
                num += ww
            elif s[i] == s[j]:
                num += 0.5 * ww
    return num / den


def test_auc_matches_bruteforce(rng):
    for trial in range(5):
        n = 60
        s = rng.normal(size=n).round(1)  # rounding forces ties
        y = (rng.uniform(size=n) > 0.4).astype(float)
        w = rng.uniform(0.5, 2.0, size=n)
        np.testing.assert_allclose(area_under_roc_curve(s, y, w),
                                   _brute_auc(s, y, w), rtol=1e-12)
        np.testing.assert_allclose(area_under_roc_curve(s, y),
                                   _brute_auc(s, y), rtol=1e-12)


def test_auc_perfect_and_random():
    y = np.asarray([0, 0, 1, 1], float)
    assert area_under_roc_curve([1, 2, 3, 4], y) == 1.0
    assert area_under_roc_curve([4, 3, 2, 1], y) == 0.0
    assert area_under_roc_curve([1, 1, 1, 1], y) == 0.5
    assert np.isnan(area_under_roc_curve([1, 2], [1, 1]))  # one class


def test_rmse_and_direction():
    assert RMSE([1, 2], [1, 2]) == 0.0
    np.testing.assert_allclose(RMSE([0, 0], [3, 4]), np.sqrt(12.5))
    assert RMSE.better_than(0.5, 1.0) and not RMSE.better_than(1.0, 0.5)
    assert AUC.better_than(0.9, 0.6) and not AUC.better_than(0.6, 0.9)
    assert AUC.better_than(0.6, float("nan")) and not AUC.better_than(float("nan"), 0.6)


def test_precision_at_k():
    s = [0.9, 0.8, 0.7, 0.1]
    y = [1, 0, 1, 1]
    assert precision_at_k(2, s, y) == 0.5
    assert precision_at_k(3, s, y) == pytest.approx(2 / 3)


def test_multi_evaluator_grouping(rng):
    # two groups with known AUCs 1.0 and 0.5 -> mean 0.75; group -1 ignored
    g = np.asarray([0, 0, 0, 0, 1, 1, 1, 1, -1])
    s = np.asarray([.1, .2, .3, .4, .5, .5, .5, .5, 9.0])
    y = np.asarray([0, 0, 1, 1, 0, 1, 0, 1, 1.0])
    me = MultiEvaluator("AUC:g", area_under_roc_curve, larger_is_better=True)
    np.testing.assert_allclose(me.evaluate_grouped(g, s, y), 0.75)


class TestSegmentedGroupedEvaluators:
    """Segment-op grouped metrics must exactly match the per-group loop
    (reference: MultiEvaluator.scala:49-64 semantics)."""

    def _random_grouped(self, rng, n=2000, num_groups=80, ties=True):
        g = rng.integers(-1, num_groups, size=n).astype(np.int64)
        s = rng.normal(size=n)
        if ties:  # heavy score ties stress the midrank path
            s = np.round(s, 1)
        y = (rng.uniform(size=n) < 0.4).astype(float)
        w = rng.uniform(0.5, 2.0, size=n)
        return g, s, y, w

    def _assert_match(self, me, g, s, y, w):
        loop = dataclasses.replace(me, segmented=None)
        for weights in (None, w):
            a = me.evaluate_grouped(g, s, y, weights)
            b = loop.evaluate_grouped(g, s, y, weights)
            np.testing.assert_allclose(a, b, rtol=1e-12, err_msg=me.name)

    def test_auc_matches_loop(self, rng):
        me, _ = parse_evaluator("AUC:g")
        assert me.segmented is not None
        self._assert_match(me, *self._random_grouped(rng))

    def test_auc_single_class_groups_dropped(self, rng):
        # groups 0/1 are single-class (NaN, dropped); group 2 mixed
        g = np.asarray([0, 0, 1, 1, 2, 2, 2, 2])
        s = np.asarray([.1, .2, .3, .4, .1, .2, .3, .4])
        y = np.asarray([1, 1, 0, 0, 0, 0, 1, 1.0])
        me, _ = parse_evaluator("AUC:g")
        np.testing.assert_allclose(me.evaluate_grouped(g, s, y), 1.0)

    def test_precision_at_k_matches_loop(self, rng):
        me, _ = parse_evaluator("PRECISION@K:3:g")
        assert me.segmented is not None
        self._assert_match(me, *self._random_grouped(rng))

    def test_rmse_and_losses_match_loop(self, rng):
        for spec in ("RMSE:g", "LOGISTIC_LOSS:g", "SQUARED_LOSS:g",
                     "POISSON_LOSS:g", "SMOOTHED_HINGE_LOSS:g"):
            me, _ = parse_evaluator(spec)
            assert me.segmented is not None, spec
            g, s, y, w = self._random_grouped(rng)
            if spec.startswith("POISSON"):
                y = np.abs(y)
            self._assert_match(me, g, s, y, w)

    def test_groups_smaller_than_min_size_skipped(self, rng):
        g = np.asarray([0, 0, 0, 1])
        s = np.asarray([.1, .5, .3, .9])
        y = np.asarray([0, 1, 1, 1.0])
        me, _ = parse_evaluator("AUC:g")
        me = dataclasses.replace(me, min_group_size=2)
        loop = dataclasses.replace(me, segmented=None)
        np.testing.assert_allclose(me.evaluate_grouped(g, s, y),
                                   loop.evaluate_grouped(g, s, y))

    def test_million_groups_fast(self, rng):
        # VERDICT round-2 item #3 gate: grouped AUC over 1e6 groups in ~1s
        n, num_groups = 4_000_000, 1_000_000
        g = rng.integers(0, num_groups, size=n)
        s = rng.normal(size=n)
        y = (rng.uniform(size=n) < 0.5).astype(float)
        me, _ = parse_evaluator("AUC:g")
        t0 = time.perf_counter()
        v = me.evaluate_grouped(g, s, y)
        dt = time.perf_counter() - t0
        assert np.isfinite(v)
        assert dt < 10.0, f"grouped AUC over 1e6 groups took {dt:.1f}s"

    def test_precision_tie_break_matches_stable_sort(self):
        # equal scores: the k slots go to earlier rows (stable descending
        # sort), exactly like the loop's argsort(-s, kind='stable')
        g = np.asarray([0, 0, 0, 0])
        s = np.asarray([.5, .5, .5, .5])
        y = np.asarray([1, 0, 0, 1.0])
        me, _ = parse_evaluator("PRECISION@K:2:g")
        loop = dataclasses.replace(me, segmented=None)
        a = me.evaluate_grouped(g, s, y)
        assert a == loop.evaluate_grouped(g, s, y) == 0.5


def test_parse_evaluator():
    e, col = parse_evaluator("AUC")
    assert e.name == "AUC" and col is None
    e, col = parse_evaluator("PRECISION@K:5:queryId")
    assert col == "queryId" and e.larger_is_better
    e, col = parse_evaluator("RMSE:userId")
    assert isinstance(e, MultiEvaluator) and col == "userId"
    with pytest.raises(ValueError):
        parse_evaluator("NOPE")
    assert default_validation_evaluator_for_task("logistic_regression").name == "AUC"


class TestDeviceEvaluators:
    """Jitted device kernels vs the numpy float64 parity oracles (ISSUE 2:
    pipelined validation keeps metrics device-resident; numpy remains the
    reference).  Under the x64 test fixture both paths run in float64, so
    agreement is tight."""

    def test_device_auc_matches_numpy(self, rng):
        import jax.numpy as jnp

        from photon_ml_tpu.evaluation.evaluators import device_auc
        for trial in range(5):
            n = 80
            s = rng.normal(size=n).round(1)  # rounding forces ties
            y = (rng.uniform(size=n) > 0.4).astype(float)
            w = rng.uniform(0.5, 2.0, size=n)
            np.testing.assert_allclose(
                float(device_auc(jnp.asarray(s), jnp.asarray(y),
                                 jnp.asarray(w))),
                area_under_roc_curve(s, y, w), rtol=1e-10)
        # unweighted path (weights=None traces its own variant)
        s = rng.normal(size=50)
        y = (rng.uniform(size=50) > 0.5).astype(float)
        np.testing.assert_allclose(
            float(device_auc(jnp.asarray(s), jnp.asarray(y))),
            area_under_roc_curve(s, y), rtol=1e-10)

    def test_device_auc_single_class_nan(self):
        import jax.numpy as jnp

        from photon_ml_tpu.evaluation.evaluators import device_auc
        v = device_auc(jnp.asarray([1.0, 2.0]), jnp.asarray([1.0, 1.0]))
        assert np.isnan(float(v))

    def test_device_rmse_and_losses_match_host(self, rng):
        import jax.numpy as jnp

        from photon_ml_tpu.evaluation.evaluators import (
            LOGISTIC_LOSS, POISSON_LOSS, RMSE, SMOOTHED_HINGE_LOSS,
            SQUARED_LOSS, rmse)
        n = 64
        s = rng.normal(size=n)
        y = (rng.uniform(size=n) > 0.5).astype(float)
        w = rng.uniform(0.5, 2.0, size=n)
        sj, yj, wj = jnp.asarray(s), jnp.asarray(y), jnp.asarray(w)
        np.testing.assert_allclose(float(RMSE.device_fn(sj, yj, wj)),
                                   rmse(s, y, w), rtol=1e-10)
        for ev in (LOGISTIC_LOSS, SQUARED_LOSS, POISSON_LOSS,
                   SMOOTHED_HINGE_LOSS):
            np.testing.assert_allclose(float(ev.device_fn(sj, yj, wj)),
                                       ev(s, y, w), rtol=1e-10)

    def test_evaluate_on_device_fallback_contract(self):
        """Evaluators without a device kernel report None so the descent
        loop takes the host path instead of crashing."""
        from photon_ml_tpu.evaluation.evaluators import Evaluator
        custom = Evaluator("CUSTOM", lambda s, y, w: 0.5,
                           larger_is_better=True)
        assert custom.device_fn is None
        assert custom.evaluate_on_device(None, None) is None
        assert AUC.evaluate_on_device is not None

    def test_loss_metric_accepts_device_arrays(self, rng):
        """Satellite bugfix: _loss_metric no longer forces device arrays
        through np.asarray (an [n] host round-trip per evaluation); device
        and numpy inputs agree."""
        import jax.numpy as jnp

        from photon_ml_tpu.evaluation.evaluators import LOGISTIC_LOSS
        n = 128
        s = rng.normal(size=n)
        y = (rng.uniform(size=n) > 0.5).astype(float)
        host = LOGISTIC_LOSS(s, y)
        dev = LOGISTIC_LOSS(jnp.asarray(s), jnp.asarray(y))
        np.testing.assert_allclose(dev, host, rtol=1e-12)
