"""Evaluator correctness vs sklearn-free closed forms and brute force.

Mirrors reference: AreaUnderROCCurveEvaluatorTest / LocalEvaluator tests /
MultiEvaluator grouping tests.
"""
import numpy as np
import pytest

from photon_ml_tpu.evaluation import (
    AUC, RMSE, MultiEvaluator, area_under_roc_curve,
    default_validation_evaluator_for_task, parse_evaluator, precision_at_k,
)


def _brute_auc(s, y, w=None):
    s, y = np.asarray(s, float), np.asarray(y, float)
    w = np.ones_like(s) if w is None else np.asarray(w, float)
    num = den = 0.0
    for i in np.nonzero(y > 0.5)[0]:
        for j in np.nonzero(y <= 0.5)[0]:
            ww = w[i] * w[j]
            den += ww
            if s[i] > s[j]:
                num += ww
            elif s[i] == s[j]:
                num += 0.5 * ww
    return num / den


def test_auc_matches_bruteforce(rng):
    for trial in range(5):
        n = 60
        s = rng.normal(size=n).round(1)  # rounding forces ties
        y = (rng.uniform(size=n) > 0.4).astype(float)
        w = rng.uniform(0.5, 2.0, size=n)
        np.testing.assert_allclose(area_under_roc_curve(s, y, w),
                                   _brute_auc(s, y, w), rtol=1e-12)
        np.testing.assert_allclose(area_under_roc_curve(s, y),
                                   _brute_auc(s, y), rtol=1e-12)


def test_auc_perfect_and_random():
    y = np.asarray([0, 0, 1, 1], float)
    assert area_under_roc_curve([1, 2, 3, 4], y) == 1.0
    assert area_under_roc_curve([4, 3, 2, 1], y) == 0.0
    assert area_under_roc_curve([1, 1, 1, 1], y) == 0.5
    assert np.isnan(area_under_roc_curve([1, 2], [1, 1]))  # one class


def test_rmse_and_direction():
    assert RMSE([1, 2], [1, 2]) == 0.0
    np.testing.assert_allclose(RMSE([0, 0], [3, 4]), np.sqrt(12.5))
    assert RMSE.better_than(0.5, 1.0) and not RMSE.better_than(1.0, 0.5)
    assert AUC.better_than(0.9, 0.6) and not AUC.better_than(0.6, 0.9)
    assert AUC.better_than(0.6, float("nan")) and not AUC.better_than(float("nan"), 0.6)


def test_precision_at_k():
    s = [0.9, 0.8, 0.7, 0.1]
    y = [1, 0, 1, 1]
    assert precision_at_k(2, s, y) == 0.5
    assert precision_at_k(3, s, y) == pytest.approx(2 / 3)


def test_multi_evaluator_grouping(rng):
    # two groups with known AUCs 1.0 and 0.5 -> mean 0.75; group -1 ignored
    g = np.asarray([0, 0, 0, 0, 1, 1, 1, 1, -1])
    s = np.asarray([.1, .2, .3, .4, .5, .5, .5, .5, 9.0])
    y = np.asarray([0, 0, 1, 1, 0, 1, 0, 1, 1.0])
    me = MultiEvaluator("AUC:g", area_under_roc_curve, larger_is_better=True)
    np.testing.assert_allclose(me.evaluate_grouped(g, s, y), 0.75)


def test_parse_evaluator():
    e, col = parse_evaluator("AUC")
    assert e.name == "AUC" and col is None
    e, col = parse_evaluator("PRECISION@K:5:queryId")
    assert col == "queryId" and e.larger_is_better
    e, col = parse_evaluator("RMSE:userId")
    assert isinstance(e, MultiEvaluator) and col == "userId"
    with pytest.raises(ValueError):
        parse_evaluator("NOPE")
    assert default_validation_evaluator_for_task("logistic_regression").name == "AUC"
