"""Loss kernels vs autodiff and closed forms.

Mirrors the reference's finite-difference style loss tests (reference:
photon-api/src/test/.../function/glm/LogisticLossFunctionTest.scala et al.).
Here we hold the losses to a stronger standard: dz/d2z must match jax.grad of
the loss exactly (not just finite differences).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import losses


ALL = [losses.LOGISTIC, losses.SQUARED, losses.POISSON, losses.SMOOTHED_HINGE]


def _labels_for(loss, rng, n):
    if loss.name in ("logistic", "smoothed_hinge"):
        return (rng.uniform(size=n) > 0.5).astype(float)
    if loss.name == "poisson":
        return rng.poisson(2.0, size=n).astype(float)
    return rng.normal(size=n)


@pytest.mark.parametrize("loss", ALL, ids=lambda l: l.name)
def test_dz_matches_autodiff(loss, rng):
    z = jnp.asarray(rng.normal(size=64) * 3)
    y = jnp.asarray(_labels_for(loss, rng, 64))
    got = loss.dz(z, y)
    want = jax.vmap(jax.grad(loss.loss, argnums=0))(z, y)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)


@pytest.mark.parametrize("loss", [l for l in ALL if l.twice_differentiable],
                         ids=lambda l: l.name)
def test_d2z_matches_autodiff(loss, rng):
    z = jnp.asarray(rng.normal(size=64) * 3)
    y = jnp.asarray(_labels_for(loss, rng, 64))
    got = loss.d2z(z, y)
    want = jax.vmap(jax.grad(jax.grad(loss.loss, argnums=0), argnums=0))(z, y)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)


def test_logistic_closed_form():
    # y=1: log(1+e^-z); y=0: log(1+e^z)
    z = jnp.asarray([-30.0, -1.0, 0.0, 1.0, 30.0])
    np.testing.assert_allclose(losses.LOGISTIC.loss(z, jnp.ones_like(z)),
                               np.log1p(np.exp(-np.asarray(z))), rtol=1e-12)
    np.testing.assert_allclose(losses.LOGISTIC.loss(z, jnp.zeros_like(z)),
                               np.log1p(np.exp(np.asarray(z))), rtol=1e-12)


def test_logistic_extreme_margins_stable():
    z = jnp.asarray([-1e4, -500.0, 500.0, 1e4])
    for y in (0.0, 1.0):
        l = losses.LOGISTIC.loss(z, jnp.full_like(z, y))
        assert bool(jnp.all(jnp.isfinite(l)))
        g = losses.LOGISTIC.dz(z, jnp.full_like(z, y))
        assert bool(jnp.all(jnp.isfinite(g)))


def test_smoothed_hinge_piecewise():
    # t = yy*z with y=1: t<0 -> 0.5-t; 0<=t<1 -> 0.5(1-t)^2; t>=1 -> 0
    z = jnp.asarray([-2.0, 0.0, 0.5, 1.0, 3.0])
    y = jnp.ones_like(z)
    np.testing.assert_allclose(losses.SMOOTHED_HINGE.loss(z, y),
                               [2.5, 0.5, 0.125, 0.0, 0.0], atol=1e-12)


def test_poisson_closed_form():
    z = jnp.asarray([0.0, 1.0, -1.0])
    y = jnp.asarray([2.0, 0.0, 5.0])
    np.testing.assert_allclose(losses.POISSON.loss(z, y),
                               np.exp(np.asarray(z)) - np.asarray(y) * np.asarray(z),
                               rtol=1e-12)


def test_means():
    z = jnp.asarray([-1.0, 0.0, 2.0])
    np.testing.assert_allclose(losses.LOGISTIC.mean(z), 1 / (1 + np.exp(-np.asarray(z))), rtol=1e-12)
    np.testing.assert_allclose(losses.SQUARED.mean(z), z)
    np.testing.assert_allclose(losses.POISSON.mean(z), np.exp(np.asarray(z)), rtol=1e-12)
