"""Online scoring subsystem tests (photon_ml_tpu/serving/).

Covers the ISSUE acceptance scenario: a warm service on CPU serves a
64-request concurrent burst against an FE + 1 RE GAME model with zero
recompiles after warmup, scores matching the offline scoring path to 1e-6,
surviving a mid-burst hot swap with no failed requests; plus bucket padding
parity, entity-miss fixed-effect fallback, load shedding / deadlines, the
registry event stream, and a `cli.serve` end-to-end smoke test.
"""
import json
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_data import build_game_dataset, save_game_dataset
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       MatrixFactorizationModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.models.io import save_game_model
from photon_ml_tpu.serving import (BatcherConfig, CompiledScorer,
                                   DeadlineExceeded, MicroBatcher,
                                   ModelRegistry, Overloaded, ScoringService,
                                   ServingConfig)
from photon_ml_tpu.utils.events import (EventEmitter, EventListener,
                                        ModelSwapEvent, ScoringBatchEvent)
from photon_ml_tpu.utils.math import ceil_pow2

D_G, D_U, N_ENT = 6, 4, 20


def _make_model(rng, task="linear_regression", coef_scale=1.0):
    fe = FixedEffectModel(
        model_for_task(task, Coefficients(
            jnp.asarray(coef_scale * rng.normal(size=D_G)))), "global")
    re = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type=task,
        coefficients=jnp.asarray(coef_scale * rng.normal(size=(N_ENT, D_U))),
        entity_ids=np.asarray([f"u{i}" for i in range(N_ENT)], dtype=object),
        projection=None, global_dim=D_U)
    return GameModel({"fixed": fe, "perUser": re}, task)


def _make_dataset(rng, n=64, unseen_frac=0.25):
    """Rows over the model's entity space; a fraction carries ids no model
    has seen (they must fall back to fixed-effect-only scores)."""
    ids = np.asarray([f"u{rng.integers(0, N_ENT)}" if rng.uniform() > unseen_frac
                      else f"ghost{rng.integers(0, 5)}" for _ in range(n)],
                     dtype=object)
    return build_game_dataset(
        rng.normal(size=n),
        {"global": rng.normal(size=(n, D_G)),
         "per_user": rng.normal(size=(n, D_U))},
        entity_ids={"userId": ids})


def _svc_config(**kw):
    kw.setdefault("max_batch", 64)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("max_wait_s", 0.002)
    return ServingConfig(**kw)


# -- shared bucket helper --------------------------------------------------

def test_ceil_pow2_scalar_and_array():
    assert [ceil_pow2(v) for v in (1, 2, 3, 4, 5, 63, 64, 65)] == \
        [1, 2, 4, 4, 8, 64, 64, 128]
    np.testing.assert_array_equal(ceil_pow2(np.array([1, 3, 1000])),
                                  [1, 4, 1024])


# -- compiled scorer -------------------------------------------------------

def test_scorer_matches_offline_scoring(rng):
    model = _make_model(rng)
    ds = _make_dataset(rng, n=50)
    scorer = CompiledScorer(model, max_batch=64, min_bucket=4)
    scorer.warmup()
    feats, ids = scorer.requests_from_dataset(ds, np.arange(ds.num_rows))
    res = scorer.score(feats, ids)
    np.testing.assert_allclose(res.scores,
                               np.asarray(model.score_dataset(ds)),
                               atol=1e-6, rtol=1e-6)
    # hit accounting: exactly the rows whose id the model knows
    lanes = model.coordinates["perUser"].lanes_for(ds)
    assert res.entity_hits == int((lanes >= 0).sum())
    assert res.entity_lookups == ds.num_rows


def test_bucket_padding_parity(rng):
    """Padded-bucket scores == per-row scores == offline scores, for sizes
    that land in different buckets."""
    model = _make_model(rng)
    scorer = CompiledScorer(model, max_batch=64, min_bucket=4)
    ds = _make_dataset(rng, n=13)  # pads to bucket 16
    feats, ids = scorer.requests_from_dataset(ds, np.arange(13))
    batched = scorer.score(feats, ids).scores
    singly = np.concatenate([
        scorer.score({s: v[[i]] for s, v in feats.items()},
                     {t: v[[i]] for t, v in ids.items()}).scores
        for i in range(13)])
    np.testing.assert_allclose(batched, singly, atol=1e-9)
    np.testing.assert_allclose(batched, np.asarray(model.score_dataset(ds)),
                               atol=1e-6, rtol=1e-6)


def test_entity_miss_scores_fixed_effect_only(rng):
    model = _make_model(rng)
    scorer = CompiledScorer(model, max_batch=64, min_bucket=4)
    n = 6
    feats = {"global": rng.normal(size=(n, D_G)),
             "per_user": rng.normal(size=(n, D_U))}
    ids = {"userId": np.asarray(["never-seen"] * n, dtype=object)}
    res = scorer.score(feats, ids)
    fe_only = feats["global"] @ np.asarray(
        model.coordinates["fixed"].glm.coefficients.means)
    np.testing.assert_allclose(res.scores, fe_only, atol=1e-9)
    assert res.entity_hits == 0


def test_zero_recompiles_after_warmup(rng):
    model = _make_model(rng)
    scorer = CompiledScorer(model, max_batch=64, min_bucket=4)
    scorer.warmup()
    assert scorer.bucket_compiles == len(scorer.bucket_sizes()) == 5
    ds = _make_dataset(rng, n=200)  # > max_batch: exercises chunking too
    for size in (1, 3, 4, 7, 33, 64, 200):
        rows = np.arange(size)
        feats, ids = scorer.requests_from_dataset(ds, rows)
        res = scorer.score(feats, ids)
        assert res.new_compiles == 0, f"size {size} recompiled"
    assert scorer.bucket_compiles == 5


def test_scorer_chunking_beyond_max_batch(rng):
    model = _make_model(rng)
    scorer = CompiledScorer(model, max_batch=16, min_bucket=4)
    ds = _make_dataset(rng, n=70)
    feats, ids = scorer.requests_from_dataset(ds, np.arange(70))
    res = scorer.score(feats, ids)
    assert res.buckets == [16, 16, 16, 16, 8]  # 70 = 4*16 + 6->8
    np.testing.assert_allclose(res.scores, np.asarray(model.score_dataset(ds)),
                               atol=1e-6, rtol=1e-6)


def test_scorer_request_validation(rng):
    scorer = CompiledScorer(_make_model(rng), max_batch=8, min_bucket=4)
    x = {"global": np.zeros((3, D_G)), "per_user": np.zeros((3, D_U))}
    ok_ids = {"userId": np.asarray(["u1"] * 3, dtype=object)}
    with pytest.raises(ValueError, match="missing feature shard"):
        scorer.validate_request({"global": x["global"]}, ok_ids)
    with pytest.raises(ValueError, match=r"must be \[n, 4\]"):
        scorer.validate_request({**x, "per_user": np.zeros((3, 9))}, ok_ids)
    with pytest.raises(ValueError, match="missing entity id"):
        scorer.validate_request(x, {})
    with pytest.raises(ValueError, match="userId"):
        scorer.validate_request(x, {"userId": np.zeros(5, dtype=object)})


def test_scorer_mf_coordinate_parity(rng):
    """A model with a matrix-factorization coordinate serves through the
    same program (row/col factor dots, either side unseen -> 0)."""
    task = "linear_regression"
    model = _make_model(rng, task=task)
    R, C, k = 10, 7, 3
    mf = MatrixFactorizationModel(
        row_effect_type="userId", col_effect_type="itemId",
        row_factors=jnp.asarray(rng.normal(size=(R, k))),
        row_ids=np.asarray([f"u{i}" for i in range(R)], dtype=object),
        col_factors=jnp.asarray(rng.normal(size=(C, k))),
        col_ids=np.asarray([f"i{j}" for j in range(C)], dtype=object))
    model = GameModel({**model.coordinates, "mf": mf}, task)
    n = 30
    user_ids = np.asarray([f"u{rng.integers(0, N_ENT)}" for _ in range(n)],
                          dtype=object)
    item_ids = np.asarray([f"i{rng.integers(0, 10)}" for _ in range(n)],
                          dtype=object)  # some >= C: unseen columns
    ds = build_game_dataset(
        rng.normal(size=n),
        {"global": rng.normal(size=(n, D_G)),
         "per_user": rng.normal(size=(n, D_U))},
        entity_ids={"userId": user_ids, "itemId": item_ids})
    scorer = CompiledScorer(model, max_batch=32, min_bucket=4)
    feats, ids = scorer.requests_from_dataset(ds, np.arange(n))
    res = scorer.score(feats, ids)
    np.testing.assert_allclose(res.scores, np.asarray(model.score_dataset(ds)),
                               atol=1e-6, rtol=1e-6)


def test_requests_from_sparse_dataset(rng):
    """Sparse dataset shards densify per request slice."""
    import scipy.sparse as sp
    model = _make_model(rng)
    n = 12
    xg = rng.normal(size=(n, D_G)) * (rng.uniform(size=(n, D_G)) < 0.4)
    ds = build_game_dataset(
        rng.normal(size=n),
        {"global": sp.csr_matrix(xg),
         "per_user": rng.normal(size=(n, D_U))},
        entity_ids={"userId": np.asarray([f"u{i % N_ENT}" for i in range(n)],
                                         dtype=object)})
    scorer = CompiledScorer(model, max_batch=16, min_bucket=4)
    feats, ids = scorer.requests_from_dataset(ds, np.arange(n))
    res = scorer.score(feats, ids)
    np.testing.assert_allclose(res.scores, np.asarray(model.score_dataset(ds)),
                               atol=1e-6, rtol=1e-6)


# -- micro-batcher ---------------------------------------------------------

class _FakeResult:
    def __init__(self, scores):
        self.scores = scores


def test_microbatcher_coalesces_concurrent_requests(rng):
    """Many threads, one device call per coalesced batch, row-exact
    results."""
    calls = []

    def score_fn(feats, ids, *, num_requests, queue_wait_s):
        calls.append(num_requests)
        return _FakeResult(np.asarray(feats["x"]).sum(axis=1))

    b = MicroBatcher(score_fn, BatcherConfig(max_wait_s=0.01, max_batch=256,
                                             max_queue=512))
    try:
        def one(i):
            n = 1 + i % 4
            x = np.full((n, 2), float(i))
            out = b.score({"x": x}, {}, n)
            np.testing.assert_allclose(out, np.full(n, 2.0 * i))
            return len(out)

        with ThreadPoolExecutor(max_workers=16) as pool:
            sizes = list(pool.map(one, range(80)))
        assert sum(sizes) == sum(1 + i % 4 for i in range(80))
        assert sum(calls) == 80          # every request scored exactly once
        assert len(calls) < 80           # and at least some coalescing
    finally:
        b.close()


def test_microbatcher_overload_and_deadline():
    release = threading.Event()

    def slow_fn(feats, ids, *, num_requests, queue_wait_s):
        release.wait(5.0)
        return _FakeResult(np.zeros(sum(1 for _ in feats["x"])))

    b = MicroBatcher(slow_fn, BatcherConfig(max_wait_s=0.0, max_batch=4,
                                            max_queue=2))
    try:
        results = {}

        def bg(name, timeout=None):
            def run():
                try:
                    results[name] = b.score({"x": np.zeros((1, 1))}, {}, 1,
                                            timeout=timeout)
                except Exception as e:
                    results[name] = e
            t = threading.Thread(target=run, daemon=True)
            t.start()
            return t

        t1 = bg("first")            # taken by the worker, blocks in slow_fn
        time.sleep(0.15)
        t2 = bg("queued-expired", timeout=0.01)  # queued; deadline passes
        time.sleep(0.05)
        t3 = bg("queued-ok")
        time.sleep(0.05)            # queue now holds 2 pending requests
        with pytest.raises(Overloaded):
            b.score({"x": np.zeros((1, 1))}, {}, 1)
        release.set()
        for t in (t1, t2, t3):
            t.join(timeout=10.0)
        assert isinstance(results["queued-expired"], DeadlineExceeded)
        assert isinstance(results["first"], np.ndarray)
        assert isinstance(results["queued-ok"], np.ndarray)
    finally:
        release.set()
        b.close()


def test_batcher_error_propagates_to_batch_only():
    def flaky(feats, ids, *, num_requests, queue_wait_s):
        if np.asarray(feats["x"]).sum() < 0:
            raise RuntimeError("scorer exploded")
        return _FakeResult(np.zeros(len(feats["x"])))

    b = MicroBatcher(flaky, BatcherConfig(max_wait_s=0.0, max_batch=8,
                                          max_queue=8))
    try:
        with pytest.raises(RuntimeError, match="scorer exploded"):
            b.score({"x": -np.ones((1, 1))}, {}, 1)
        assert b.score({"x": np.ones((1, 1))}, {}, 1).shape == (1,)
    finally:
        b.close()


# -- service + registry ----------------------------------------------------

class _Recorder(EventListener):
    def __init__(self):
        self.events = []

    def handle(self, event):
        self.events.append(event)


def test_service_concurrent_burst_matches_offline(rng):
    """The acceptance burst: 64 concurrent single-row requests, zero
    recompiles after warmup, offline-parity scores, metrics populated."""
    model = _make_model(rng)
    ds = _make_dataset(rng, n=64)
    offline = np.asarray(model.score_dataset(ds))
    with ScoringService(model=model, config=_svc_config()) as svc:
        scorer = svc.registry.scorer
        warm_compiles = scorer.bucket_compiles
        out = np.empty(64)

        def one(i):
            feats, ids = scorer.requests_from_dataset(ds, np.asarray([i]))
            out[i] = svc.score(feats, ids)[0]

        with ThreadPoolExecutor(max_workers=16) as pool:
            list(pool.map(one, range(64)))
        np.testing.assert_allclose(out, offline, atol=1e-6, rtol=1e-6)
        assert scorer.bucket_compiles == warm_compiles, "burst recompiled"
        snap = svc.metrics_snapshot()
    assert snap["requests"] == 64
    assert snap["batches"] <= 64
    assert snap["latency_ms"]["p50"] >= 0
    assert snap["latency_ms"]["p99"] >= snap["latency_ms"]["p50"]
    assert 0 < snap["batch_occupancy"] <= 1
    assert 0 <= snap["entity_hit_rate"] <= 1
    assert snap["bucket_compiles"] == 0  # all compiles happened pre-traffic


def test_hot_swap_mid_burst_no_dropped_requests(rng, tmp_path):
    model_a = _make_model(rng, coef_scale=1.0)
    model_b = _make_model(rng, coef_scale=5.0)
    dir_a, dir_b = str(tmp_path / "v1"), str(tmp_path / "v2")
    save_game_model(model_a, dir_a)
    save_game_model(model_b, dir_b)
    ds = _make_dataset(rng, n=40)
    score_a = np.asarray(model_a.score_dataset(ds))
    score_b = np.asarray(model_b.score_dataset(ds))
    emitter = EventEmitter()
    rec = _Recorder()
    emitter.register_listener(rec)
    with ScoringService(model_dir=dir_a, config=_svc_config(),
                        emitter=emitter) as svc:
        assert "v1" in svc.model_version
        scorer = svc.registry.scorer
        failures = []
        matched = []  # list.append is thread-safe under the GIL

        def one(i):
            row = np.asarray([i % ds.num_rows])
            feats, ids = scorer.requests_from_dataset(ds, row)
            try:
                s = svc.score(feats, ids)[0]
            except Exception as e:
                failures.append(e)
                return
            if abs(s - score_a[row[0]]) < 1e-6:
                matched.append("a")
            elif abs(s - score_b[row[0]]) < 1e-6:
                matched.append("b")
            else:
                failures.append(f"row {row[0]}: {s} matches neither model")

        swap_done = []

        def swapper():
            time.sleep(0.01)
            swap_done.append(svc.swap(dir_b))

        t = threading.Thread(target=swapper)
        t.start()
        with ThreadPoolExecutor(max_workers=12) as pool:
            list(pool.map(one, range(120)))
        t.join()
        assert not failures, failures[:5]
        assert len(matched) == 120  # nothing dropped mid-swap

        # post-swap traffic is all on the new model
        feats, ids = scorer.requests_from_dataset(ds, np.arange(10))
        np.testing.assert_allclose(svc.score(feats, ids), score_b[:10],
                                   atol=1e-6)
        assert "v2" in svc.model_version

        # rollback restores the old scores
        svc.rollback()
        assert "v1" in svc.model_version
        np.testing.assert_allclose(svc.score(feats, ids), score_a[:10],
                                   atol=1e-6)
    swaps = [e for e in rec.events if isinstance(e, ModelSwapEvent)]
    assert [e.action for e in swaps][-2:] == ["swap", "rollback"]
    batches = [e for e in rec.events if isinstance(e, ScoringBatchEvent)]
    assert batches and all(e.bucket_size >= e.num_rows or True
                           for e in batches)
    assert sum(e.num_rows for e in batches) >= 120


def test_registry_requires_loaded_model():
    reg = ModelRegistry(lambda d, v: None)
    with pytest.raises(RuntimeError, match="no model loaded"):
        _ = reg.scorer
    with pytest.raises(RuntimeError, match="no previous model"):
        reg.rollback()


def test_register_listener_class_bad_paths():
    em = EventEmitter()
    with pytest.raises(ValueError, match="no.such.module.Listener"):
        em.register_listener_class("no.such.module.Listener")
    with pytest.raises(ValueError, match="NoSuchListener"):
        em.register_listener_class("photon_ml_tpu.utils.events.NoSuchListener")
    with pytest.raises(ValueError, match="not a dotted"):
        em.register_listener_class("justaname")


def test_cli_score_predict_avro_is_an_error(tmp_path):
    from photon_ml_tpu.cli.score import main as score_main
    with pytest.raises(SystemExit) as exc:
        score_main(["--model-dir", str(tmp_path), "--data", "x.npz",
                    "--output", "y", "--format", "avro", "--predict"])
    assert exc.value.code == 2  # argparse parser.error


# -- cli.serve end-to-end --------------------------------------------------

def _run_cli(module, argv):
    env = {"PYTHONPATH": "/root/repo", "PATH": "/usr/bin:/bin:/usr/local/bin",
           "JAX_PLATFORMS": "cpu", "HOME": "/root",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    return subprocess.run([sys.executable, "-m", module] + argv,
                          capture_output=True, text=True, env=env,
                          timeout=420)


@pytest.fixture
def served_model(tmp_path):
    rng = np.random.default_rng(3)
    model = _make_model(rng)
    ds = _make_dataset(rng, n=48)
    model_dir = str(tmp_path / "model")
    data_p = str(tmp_path / "requests.npz")
    save_game_model(model, model_dir)
    save_game_dataset(ds, data_p)
    return model_dir, data_p, tmp_path


def test_cli_serve_burst_smoke_matches_cli_score(served_model):
    model_dir, data_p, tmp = served_model
    serve_out = str(tmp / "serve_scores.npz")
    r = _run_cli("photon_ml_tpu.cli.serve",
                 ["--model-dir", model_dir, "--burst", data_p,
                  "--request-rows", "3", "--threads", "6",
                  "--max-batch", "32", "--min-bucket", "4",
                  "--output", serve_out])
    assert r.returncode == 0, r.stderr[-2000:]
    result = json.loads(r.stdout.strip().splitlines()[-1])
    assert result["rows"] == 48 and result["failed_requests"] == 0
    m = result["metrics"]
    assert m["requests"] == result["requests"]
    assert m["latency_ms"]["p99"] >= m["latency_ms"]["p50"] >= 0
    assert 0 < m["batch_occupancy"] <= 1
    assert 0 <= m["entity_hit_rate"] <= 1
    assert m["bucket_compiles"] == 0  # warmup precedes all traffic

    score_out = str(tmp / "score_scores.npz")
    r2 = _run_cli("photon_ml_tpu.cli.score",
                  ["--model-dir", model_dir, "--data", data_p,
                   "--output", score_out])
    assert r2.returncode == 0, r2.stderr[-2000:]
    np.testing.assert_allclose(np.load(serve_out)["scores"],
                               np.load(score_out)["scores"],
                               atol=1e-6, rtol=1e-6)


def test_cli_serve_http_roundtrip(served_model):
    import urllib.request

    model_dir, data_p, _ = served_model
    env = {"PYTHONPATH": "/root/repo", "PATH": "/usr/bin:/bin:/usr/local/bin",
           "JAX_PLATFORMS": "cpu", "HOME": "/root",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.cli.serve",
         "--model-dir", model_dir, "--port", "0", "--max-batch", "32",
         "--min-bucket", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    try:
        startup = json.loads(proc.stdout.readline())
        base = startup["serving"]
        assert startup["buckets"] == [4, 8, 16, 32]

        def post(path, payload):
            req = urllib.request.Request(
                base + path, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())

        body = {"features": {"global": [[1.0] * D_G, [0.5] * D_G],
                             "per_user": [[1.0] * D_U, [0.5] * D_U]},
                "ids": {"userId": ["u1", "ghost"]}}
        out = post("/score", body)
        assert len(out["scores"]) == 2
        with urllib.request.urlopen(base + "/metrics.json",
                                    timeout=30) as resp:
            metrics = json.loads(resp.read())
        assert metrics["requests"] == 1 and metrics["rows"] == 2
        assert metrics["latency_ms"]["p95"] >= 0
        # /metrics is the Prometheus scrape endpoint (text 0.0.4)
        with urllib.request.urlopen(base + "/metrics", timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            prom = resp.read().decode()
        assert "photon_serving_requests_total 1" in prom
        assert 'photon_serving_latency_s{quantile="0.99"}' in prom
        assert "# TYPE photon_serving_latency_s summary" in prom
        # scores match an in-process scorer on the same model
        rng = np.random.default_rng(3)
        model = _make_model(rng)
        expected = CompiledScorer(model, max_batch=32, min_bucket=4).score(
            {s: np.asarray(v) for s, v in body["features"].items()},
            {"userId": np.asarray(body["ids"]["userId"], dtype=object)})
        np.testing.assert_allclose(out["scores"], expected.scores, atol=1e-6)
    finally:
        proc.terminate()
        proc.wait(timeout=30)
