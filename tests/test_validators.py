"""DataValidators + event-system tests.

reference: photon-client/.../data/DataValidators.scala:33-332 (per-task row
checks with VALIDATE_FULL/SAMPLE/DISABLED gating) and
event/{Event,EventEmitter,EventListener}.scala.
"""
import numpy as np
import pytest

from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.data.validators import (
    DataValidationError, DataValidationType, validate_game_dataset,
)
from photon_ml_tpu.utils.events import (
    EventEmitter, EventListener, LoggingEventListener, OptimizationLogEvent,
    TrainingFinishEvent, TrainingStartEvent,
)


def _clean(n=20, d=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (rng.uniform(size=n) < 0.5).astype(float)
    return x, y


class TestValidators:
    def test_clean_data_passes_all_tasks(self):
        x, y = _clean()
        ds = build_game_dataset(y, {"global": x}, offsets=np.zeros(20),
                                weights=np.ones(20))
        for task in ("logistic_regression", "linear_regression",
                     "smoothed_hinge_loss_linear_svm"):
            validate_game_dataset(ds, task)
        validate_game_dataset(
            build_game_dataset(np.abs(y), {"global": x}), "poisson_regression")

    def test_non_binary_label_logistic(self):
        x, y = _clean()
        y[7] = 2.0
        ds = build_game_dataset(y, {"global": x})
        with pytest.raises(DataValidationError, match="non-binary.*row 7"):
            validate_game_dataset(ds, "logistic_regression")
        # same labels are fine for linear regression
        validate_game_dataset(ds, "linear_regression")

    def test_non_finite_label_linear(self):
        x, y = _clean()
        y[3] = np.nan
        ds = build_game_dataset(y, {"global": x})
        with pytest.raises(DataValidationError, match="non-finite label.*row 3"):
            validate_game_dataset(ds, "linear_regression")

    def test_negative_label_poisson(self):
        x, y = _clean()
        y[11] = -1.0
        ds = build_game_dataset(y, {"global": x})
        with pytest.raises(DataValidationError, match="negative label.*row 11"):
            validate_game_dataset(ds, "poisson_regression")

    def test_non_finite_feature_names_row_and_column(self):
        x, y = _clean()
        x[5, 2] = np.inf
        ds = build_game_dataset(y, {"global": x})
        with pytest.raises(DataValidationError,
                           match="non-finite feature.*row 5.*'global' column 2"):
            validate_game_dataset(ds, "logistic_regression")

    def test_non_finite_offset_and_weight(self):
        x, y = _clean()
        off = np.zeros(20)
        off[2] = np.inf
        ds = build_game_dataset(y, {"global": x}, offsets=off)
        with pytest.raises(DataValidationError, match="non-finite offset.*row 2"):
            validate_game_dataset(ds, "logistic_regression")
        w = np.ones(20)
        w[9] = np.nan
        ds = build_game_dataset(y, {"global": x}, weights=w)
        with pytest.raises(DataValidationError, match="non-finite weight.*row 9"):
            validate_game_dataset(ds, "logistic_regression")

    def test_multiple_errors_all_reported(self):
        x, y = _clean()
        y[0] = 3.0
        x[1, 0] = np.nan
        ds = build_game_dataset(y, {"global": x})
        with pytest.raises(DataValidationError) as e:
            validate_game_dataset(ds, "logistic_regression")
        msg = str(e.value)
        assert "non-binary" in msg and "non-finite feature" in msg

    def test_disabled_skips_everything(self):
        x, y = _clean()
        y[:] = np.nan
        x[:] = np.inf
        ds = build_game_dataset(y, {"global": x})
        validate_game_dataset(ds, "logistic_regression",
                              DataValidationType.VALIDATE_DISABLED)
        validate_game_dataset(ds, "logistic_regression", "disabled")

    def test_sample_mode_catches_pervasive_corruption(self):
        # reference: VALIDATE_SAMPLE checks a 10% sample — with every row bad
        # it must still fail
        x, y = _clean(n=500)
        y[:] = np.nan
        ds = build_game_dataset(y, {"global": x})
        with pytest.raises(DataValidationError):
            validate_game_dataset(ds, "linear_regression",
                                  DataValidationType.VALIDATE_SAMPLE)


class _Recorder(EventListener):
    def __init__(self):
        self.events = []
        self.closed = False

    def handle(self, event):
        self.events.append(event)

    def close(self):
        self.closed = True


class _Broken(EventListener):
    def handle(self, event):
        raise RuntimeError("boom")


class TestEvents:
    def test_emitter_fanout_and_close(self):
        em = EventEmitter()
        rec = _Recorder()
        em.register_listener(rec)
        em.send_event(TrainingStartEvent(1.0))
        em.send_event(TrainingFinishEvent(2.0))
        assert [type(e) for e in rec.events] == [TrainingStartEvent,
                                                 TrainingFinishEvent]
        em.clear_listeners()
        assert rec.closed

    def test_broken_listener_does_not_kill_training(self):
        em = EventEmitter()
        rec = _Recorder()
        em.register_listener(_Broken())
        em.register_listener(rec)
        em.send_event(TrainingStartEvent(0.0))  # must not raise
        assert len(rec.events) == 1

    def test_register_by_class_path(self):
        em = EventEmitter()
        em.register_listener_class(
            "photon_ml_tpu.utils.events.LoggingEventListener")
        assert isinstance(em._listeners[0], LoggingEventListener)

    def test_estimator_emits_optimization_log(self):
        from photon_ml_tpu.game import GameEstimator, GameTrainingConfig
        from photon_ml_tpu.game.config import (FixedEffectCoordinateConfig,
                                               GLMOptimizationConfig)
        rng = np.random.default_rng(1)
        x, y = _clean(n=64, d=4, seed=1)
        ds = build_game_dataset(y, {"global": x})
        cfg = GameTrainingConfig(
            task_type="logistic_regression",
            coordinates={"fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(regularization_weight=1.0))},
            updating_sequence=["fixed"], num_outer_iterations=1)
        em = EventEmitter()
        rec = _Recorder()
        em.register_listener(rec)
        GameEstimator(cfg, emitter=em).fit(ds, ds)
        kinds = [type(e) for e in rec.events]
        assert kinds[0] is TrainingStartEvent
        assert OptimizationLogEvent in kinds
        assert kinds[-1] is TrainingFinishEvent
        log = next(e for e in rec.events if isinstance(e, OptimizationLogEvent))
        assert log.regularization_weights == {"fixed": 1.0}
        assert len(log.objective_history) == 1
        assert log.final_metrics
